"""Serving-frontend metrics, exported through the existing
:mod:`raft_tpu.core.tracing` registry.

Per-stage latency **histograms** (log2 buckets, p50/p95/p99 estimates):

- ``serving.batcher.queue_wait_seconds``   — admission → batch assembly
- ``serving.batcher.assembly_seconds``     — group pop + block concat
- ``serving.batcher.execute_seconds``      — device execute (blocked)
- ``serving.batcher.split_seconds``        — result re-split + handle set
- ``serving.batcher.e2e_seconds``          — admission → handle complete

**Counters** (throughput / shed / occupancy):

- ``serving.admission.accepted`` / ``.rejected``  — admission outcomes
- ``serving.batcher.requests`` / ``.rows``        — dispatched work
- ``serving.batcher.batches``                     — executor calls made
- ``serving.batcher.shed_deadline``               — expired → shed
- ``serving.batcher.cancelled``                   — cancelled in queue
- ``serving.batcher.shutdown_shed``               — shed at close()
- ``serving.execute.calls`` / ``.rows`` /
  ``.modeled_flops`` / ``.modeled_bytes``         — executor dispatches
  priced by each executable's compile-time ``cost_analysis()``

**Gauges** (PR 6 graftscope):

- ``serving.admission.queue_depth`` / ``.shed_level`` /
  ``.arrival_rate_hz``                            — admission state
- ``serving.executable.<digest>.flops`` /
  ``.bytes_accessed`` / ``.peak_hbm_bytes``       — per-executable cost
- ``serving.executor.cached_executables``         — AOT cache size
- ``serving.collective.<family>.<wire>.<probe_wire>.*_bytes``
                                                  — modeled mesh wire

Batch **occupancy** — the coalescing win the ISSUE's acceptance
criterion gates on — is derived, not stored: ``requests / batches``
(and ``rows / batches``) from one counters snapshot. Likewise the
**achieved-bandwidth** numbers (:func:`derived`): modeled bytes/flops
over the measured execute-latency sum — the TPU-KNN roofline
accounting as a running metric, from the same inputs the BENCH rider
reports — plus the executor cache hit-rate.
"""

from __future__ import annotations

from raft_tpu.core import tracing

PREFIX = "serving.batcher."

QUEUE_WAIT = PREFIX + "queue_wait_seconds"
ASSEMBLY = PREFIX + "assembly_seconds"
EXECUTE = PREFIX + "execute_seconds"
SPLIT = PREFIX + "split_seconds"
E2E = PREFIX + "e2e_seconds"


def observe_stage(name: str, seconds: float) -> None:
    """Record one stage latency into its histogram."""
    tracing.observe(name, seconds)


def batch_dispatched(n_requests: int, n_rows: int) -> None:
    """Count one dispatched micro-batch."""
    tracing.inc_counter(PREFIX + "batches")
    tracing.inc_counter(PREFIX + "requests", n_requests)
    tracing.inc_counter(PREFIX + "rows", n_rows)


def occupancy() -> dict:
    """Derived batch-occupancy stats: mean requests and rows per
    dispatched micro-batch (1.0 requests/batch == no coalescing)."""
    batches = tracing.get_counter(PREFIX + "batches")
    if batches == 0:
        return {"batches": 0, "requests_per_batch": 0.0,
                "rows_per_batch": 0.0}
    return {
        "batches": int(batches),
        "requests_per_batch":
            tracing.get_counter(PREFIX + "requests") / batches,
        "rows_per_batch": tracing.get_counter(PREFIX + "rows") / batches,
    }


def derived() -> dict:
    """Metrics computed from one counters read: executor cache
    hit-rate and live achieved GB/s / GFLOP/s (modeled bytes & flops
    from compile-time cost analysis, divided by the measured execute
    histogram's latency sum)."""
    hits = tracing.get_counter("serving.cache_hits")
    misses = tracing.get_counter("serving.cache_misses")
    exec_s = tracing.get_histogram(EXECUTE).snapshot()["sum"]
    out = {
        "cache_hit_rate": hits / (hits + misses) if hits + misses else 0.0,
        "execute_seconds_total": exec_s,
        "modeled_bytes_total":
            tracing.get_counter("serving.execute.modeled_bytes"),
        "modeled_flops_total":
            tracing.get_counter("serving.execute.modeled_flops"),
    }
    out["achieved_gbps"] = (
        out["modeled_bytes_total"] / exec_s / 1e9 if exec_s > 0 else 0.0)
    out["achieved_gflops"] = (
        out["modeled_flops_total"] / exec_s / 1e9 if exec_s > 0 else 0.0)
    return out


def snapshot() -> dict:
    """One scrape of the whole serving surface: counters + gauges +
    per-stage histogram summaries + derived occupancy and achieved
    bandwidth (the bench rider's, the exporter's, and any monitoring
    agent's single entry point)."""
    return {
        "counters": tracing.counters("serving."),
        "gauges": tracing.gauges("serving."),
        "histograms": tracing.histograms(PREFIX),
        "occupancy": occupancy(),
        "derived": derived(),
    }


def reset() -> None:
    """Zero every serving counter, gauge, histogram, and the span
    flight recorder — test/bench isolation."""
    tracing.reset_counters("serving.")
    tracing.reset_gauges("serving.")
    tracing.reset_histograms(PREFIX)
    tracing.reset_spans()
