"""Multi-process bootstrap — analog of raft-dask's NCCL-uniqueId dance
(``raft_dask/common/comms.py:137-215`` create_nccl_uniqueid + per-worker
``inject_comms_on_handle``) and of ``initialize_mpi_comms``
(``comms/mpi_comms.hpp:60``).

On TPU the rendezvous is ``jax.distributed.initialize`` (coordinator
address + process id replace the NCCL uniqueId broadcast); the "clique"
is the global device mesh; injection is ``Resources(mesh=..., comms=...)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.comms.comms import Comms


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join the multi-process clique (``jax.distributed.initialize``).

    Role of ``Comms.init`` (``raft_dask/common/comms.py:172-215``): after
    this, ``jax.devices()`` spans every process's chips and meshes built
    by :func:`make_mesh` are global. On Cloud TPU all arguments
    auto-detect from the runtime environment.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def make_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
    devices=None,
) -> Mesh:
    """Global mesh over all (or given) devices; the TPU's comms clique.

    With multiple axes this is the 2D row/col process grid the reference
    builds with ``comm_split`` + ``set_subcomm``."""
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    return Mesh(np.array(devs).reshape(tuple(shape)), tuple(axis_names))


def local_comms(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
) -> Comms:
    """Comms over all locally visible devices — the test-time analog of
    the reference's LocalCUDACluster trick (SURVEY.md §4): virtual CPU
    devices via ``--xla_force_host_platform_device_count`` stand in for a
    multi-host clique."""
    return Comms(make_mesh(axis_names, shape), axis_names[0])
