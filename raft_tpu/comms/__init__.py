"""Distributed communication — TPU-native re-design of ``raft/comms/``
(SURVEY.md §2.6).

The reference injects a virtual collectives interface (``comms_t``,
``core/comms.hpp:242``) backed by NCCL+UCX (``comms/std_comms.hpp``) or
MPI (``comms/mpi_comms.hpp``) into the resources handle, bootstrapped by
Dask (``raft_dask.common.Comms``) or MPI.

On TPU the transport is the ICI/DCN fabric driven by XLA collectives:
``Comms`` wraps a ``jax.sharding.Mesh`` axis, the collectives are
``jax.lax`` primitives usable inside ``shard_map``/``pjit`` programs, and
bootstrap is ``jax.distributed.initialize``. ``comm_split`` becomes mesh
axis subdivision.
"""

from raft_tpu.comms.comms import (
    Comms,
    Op,
    allgather,
    allgather_quantized,
    allgather_wire,
    allreduce,
    alltoall,
    barrier,
    bcast,
    device_recv,
    device_send,
    device_sendrecv,
    gather,
    mark_varying,
    reduce,
    reducescatter,
    resolve_probe_wire_dtype,
    resolve_wire_dtype,
)
from raft_tpu.comms.bootstrap import (
    initialize,
    local_comms,
    make_mesh,
)

__all__ = [
    "Comms",
    "Op",
    "allreduce",
    "allgather",
    "allgather_quantized",
    "allgather_wire",
    "resolve_probe_wire_dtype",
    "resolve_wire_dtype",
    "alltoall",
    "barrier",
    "bcast",
    "gather",
    "reduce",
    "reducescatter",
    "device_send",
    "device_recv",
    "device_sendrecv",
    "mark_varying",
    "initialize",
    "local_comms",
    "make_mesh",
]
