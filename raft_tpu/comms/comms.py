"""``comms_t``-shaped collectives over XLA — analog of
``core/comms.hpp:125-215`` (``comms_iface``) / ``:242`` (``comms_t``).

Free functions mirror the reference's collective set (allreduce, bcast,
reduce, allgather, gather, reducescatter, alltoall, p2p send/recv) as
``jax.lax`` calls valid inside a ``shard_map``-decorated program over a
named mesh axis — the TPU's NCCL ring is the ICI torus and XLA schedules
the transfers. ``Comms`` packages a mesh + axis with rank/size accessors
and a ``run`` helper so algorithms can be written against the same
"get the comms, call collectives" shape as the reference
(``resource::get_comms(handle).allreduce(...)``).

Unlike NCCL, these collectives are *compiled into* the program: there is
no stream to synchronize and no comm to abort — XLA's SPMD partitioner
proves shape agreement at trace time, which is why the reference's
error-propagating ``sync_stream`` barrier (``core/comms.hpp:282-291``)
reduces to :func:`barrier` here.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core import tracing


def _count_collective(family: str, tree) -> None:
    """Trace-time calls/bytes accounting for one collective veneer call
    (PR 7 graftscope v2): bumps ``comms.<family>.calls`` and
    ``comms.<family>.modeled_bytes`` (summed over the payload pytree's
    static shapes — available on tracers) under one lock. This runs as
    plain Python while the program is being *traced*, so the traced
    body gains no ops and no host syncs; AOT executables trace once,
    so the steady-state dispatch cost is exactly zero. The counters
    therefore inventory the collective families (and modeled per-shard
    payload bytes) compiled into the process's programs — the wire-cost
    ledger a scrape reads next to the ``serving.collective.*`` payload
    gauges."""
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        nbytes += n * jnp.dtype(dtype).itemsize
    tracing.inc_counters({
        f"comms.{family}.calls": 1.0,
        f"comms.{family}.modeled_bytes": float(nbytes),
    })


def timed_dispatch(family: str, thunk: Callable, axis: str = "data", *,
                   modeled_bytes: float = 0.0,
                   trace_ids: Tuple[int, ...] = (),
                   attrs: Optional[dict] = None):
    """Host-side timed dispatch of one collective-bearing program —
    the PR 6 discipline applied to the mesh: timing wraps the *call
    site* of the compiled program (``thunk``), never the traced body,
    so no host syncs ride into ``shard_map``. Records a
    ``comms.dispatch.<family>`` span into the flight recorder and
    bumps ``comms.dispatch.<family>.{calls,seconds,modeled_bytes}``
    under one lock. ``modeled_bytes`` is the caller's per-dispatch
    wire model (``collective_payload_model``); ``axis`` names the mesh
    axis whose collectives the dispatch carries (span attr only).

    Returns ``thunk()``'s result unchanged. Note the timing covers
    dispatch (and whatever the thunk itself blocks on) — callers that
    want readiness-inclusive timing block inside the thunk, as the
    traced direct-search entries do."""
    t0 = time.perf_counter()
    out = thunk()
    t1 = time.perf_counter()
    a = {"axis": axis, "modeled_bytes": float(modeled_bytes)}
    a.update(attrs or {})
    tracing.record_span(f"comms.dispatch.{family}", t0, t1,
                        trace_ids=trace_ids, attrs=a)
    tracing.inc_counters({
        f"comms.dispatch.{family}.calls": 1.0,
        f"comms.dispatch.{family}.seconds": t1 - t0,
        f"comms.dispatch.{family}.modeled_bytes": float(modeled_bytes),
    })
    return out


class Op(enum.Enum):
    """Reduction ops (``core/comms.hpp`` ``op_t``: SUM/PROD/MIN/MAX)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map`` — THE spelling every mesh program
    in this repo goes through: jax >= 0.6 exposes ``jax.shard_map``
    (validity-checking flag named ``check_vma``); 0.4.x/0.5.x ship it
    as ``jax.experimental.shard_map.shard_map`` (``check_rep``). The
    compat alias plays the same role as ``ops.fused_topk``'s
    ``_COMPILER_PARAMS`` rename shim does for Pallas."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis: str) -> int:
    """Static mesh-axis size inside a mapped program. jax >= 0.6 has
    ``jax.lax.axis_size``; earlier versions statically fold
    ``psum(1, axis)`` — the classic idiom."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


# ---------------------------------------------------------------------------
# collectives — call inside shard_map over the named axis
# ---------------------------------------------------------------------------


def _allreduce_impl(x, op: Op, axis: str):
    """Uncounted all-reduce body — delegating veneers (:func:`reduce`,
    :func:`reducescatter`'s non-SUM branch) call this so one logical
    collective bumps the ledger exactly once, under its own family."""
    if op == Op.SUM:
        return jax.lax.psum(x, axis)
    if op == Op.MAX:
        return jax.lax.pmax(x, axis)
    if op == Op.MIN:
        return jax.lax.pmin(x, axis)
    # PROD: no native pprod — gather then reduce (correct for any sign)
    return jnp.prod(jax.lax.all_gather(x, axis), axis=0)


def allreduce(x, op: Op = Op.SUM, axis: str = "data"):
    """``comms_t::allreduce`` → psum/pmax/pmin (XLA all-reduce on ICI)."""
    _count_collective("allreduce", x)
    return _allreduce_impl(x, op, axis)


def bcast(x, root: int = 0, axis: str = "data"):
    """``comms_t::bcast``: every rank ends with root's value."""
    _count_collective("bcast", x)
    rank = jax.lax.axis_index(axis)
    contrib = jnp.where(rank == root, x, jnp.zeros_like(x))
    return jax.lax.psum(contrib, axis)


def reduce(x, root: int = 0, op: Op = Op.SUM, axis: str = "data"):
    """``comms_t::reduce``: the reduced value (the reference only
    guarantees it on root; here every rank gets it, a superset).

    Cost note (VERDICT r2 weak #6): XLA exposes no root-only
    collective, but on the ICI torus this superset is NOT an R× tax —
    ring all-reduce and optimal reduce-to-root both move ~(R-1)/R of
    the payload per link; only the final broadcast leg (~1× payload)
    is extra. The same argument covers :func:`gather` vs a true
    root-only gather (ring allgather's per-link traffic equals the
    hop-by-hop forwarding a rooted gather needs). DCN-spanning meshes
    are where a rooted variant would pay; revisit if a DCN profile
    shows these hot."""
    _count_collective("reduce", x)
    return _allreduce_impl(x, op, axis)


def allgather(x, axis: str = "data", tiled: bool = False):
    """``comms_t::allgather``: stack (or concat when ``tiled``) every
    rank's block along a new leading axis."""
    _count_collective("allgather", x)
    return jax.lax.all_gather(x, axis, tiled=tiled)


# low-precision wire formats for result-carrying collectives — the
# EQuARX move (PAPERS.md): the ICI payload shrinks, the math around the
# collective stays full precision. "f32" is the identity.
WIRE_DTYPES = ("f32", "bf16")


def resolve_wire_dtype(wire_dtype: str):
    """Map a ``wire_dtype`` param to its jnp dtype (validating)."""
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be one of {WIRE_DTYPES}, got {wire_dtype!r}")
    return jnp.float32 if wire_dtype == "f32" else jnp.bfloat16


def allgather_wire(x, axis: str = "data", wire_dtype: str = "f32"):
    """:func:`allgather` with an optional low-precision wire format:
    the payload is cast to ``wire_dtype`` *before* the collective (so
    the gather moves half the bytes for bf16) and upcast back after.
    Callers that merge gathered candidates should re-rank the ties the
    compression creates deterministically (the distributed searches
    tie-break by exact id)."""
    wd = resolve_wire_dtype(wire_dtype)
    if x.dtype == wd:
        _count_collective("allgather_wire", x)
        return jax.lax.all_gather(x, axis)
    xw = x.astype(wd)
    _count_collective("allgather_wire", xw)
    return jax.lax.all_gather(xw, axis).astype(x.dtype)


# wire formats for result-*reducing* collectives (allreduce /
# reducescatter): SUM tolerates int8 too — EQuARX's recipe quantizes
# per feature block, moves codes + scale planes on the wire, and sums
# in ONE dequantized f32 epilog, so the narrow wire never compounds
# per-hop rounding
REDUCE_WIRE_DTYPES = ("f32", "bf16", "int8")

# feature-block width of the EQuARX block-wise scales: one f32 scale
# per 128 payload elements — the lane width, and small enough that one
# outlier only poisons its own block's resolution
QUANT_BLOCK = 128


def resolve_reduce_wire_dtype(wire_dtype: str) -> str:
    """Validate a reducing-collective ``wire_dtype`` (identity mapping —
    ``int8`` has no jnp carrier; the quantized collectives pack it with
    explicit block-wise scale planes)."""
    if wire_dtype not in REDUCE_WIRE_DTYPES:
        raise ValueError(
            f"reduce wire_dtype must be one of {REDUCE_WIRE_DTYPES}, "
            f"got {wire_dtype!r}")
    return wire_dtype


def _quantize_blocks(x, block: int):
    """Symmetric EQuARX block quantization along the last axis: pad to
    a multiple of ``block``, one f32 scale (``max|block| / 127``) per
    feature block. Returns ``(codes int8 (..., nb, block),
    scales f32 (..., nb, 1))`` — the uncounted prolog shared by the
    quantized reducing collectives."""
    n = x.shape[-1]
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = x.reshape(x.shape[:-1] + (nb, block))
    scale = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q8 = jnp.clip(jnp.round(xb * (127.0 / scale)), -127, 127)
    return q8.astype(jnp.int8), scale


def _dequantize_blocks(xb, n: int):
    """Flatten a dequantized (..., nb, block) f32 block view back to
    (..., n) — the epilog twin of :func:`_quantize_blocks`."""
    flat = xb.reshape(xb.shape[:-2] + (xb.shape[-2] * xb.shape[-1],))
    return flat[..., :n]


def allreduce_quantized(x, op: Op = Op.SUM, axis: str = "data",
                        wire_dtype: str = "f32",
                        block: int = QUANT_BLOCK):
    """:func:`allreduce` with an opt-in quantized wire (the EQuARX
    move applied to the *reducing* collectives — the distributed
    k-means centroid-sum path):

    - ``"f32"``: delegates to the exact all-reduce (counted under this
      veneer's own ledger family).
    - integer payloads (counts): ALWAYS the exact int32 wire,
      whatever ``wire_dtype`` says — quantizing a count is never
      acceptable, and int32 already matches f32's wire bytes.
    - ``"bf16"``: the payload travels as bf16 and every rank's
      contribution is summed in ONE f32 epilog (gather + sum), so the
      narrow wire never compounds per-hop rounding.
    - ``"int8"``: block-wise scales (:data:`QUANT_BLOCK` features per
      f32 scale) ride beside the int8 codes; one dequantized f32
      epilog sums the per-rank contributions.

    Narrow wires are SUM-only (MAX/MIN/PROD of quantized codes would
    reduce *rounded* values with no epilog to repair them)."""
    resolve_reduce_wire_dtype(wire_dtype)
    if jnp.issubdtype(x.dtype, jnp.integer):
        xi = x.astype(jnp.int32)
        _count_collective("allreduce_quantized", xi)
        return _allreduce_impl(xi, op, axis).astype(x.dtype)
    if wire_dtype == "f32":
        _count_collective("allreduce_quantized", x)
        return _allreduce_impl(x, op, axis)
    if op != Op.SUM:
        raise ValueError(
            f"quantized allreduce wires are SUM-only, got {op}")
    if wire_dtype == "bf16":
        xw = x.astype(jnp.bfloat16)
        _count_collective("allreduce_quantized", xw)
        return jnp.sum(jax.lax.all_gather(xw, axis).astype(jnp.float32),
                       axis=0)
    q8, scale = _quantize_blocks(x, block)
    _count_collective("allreduce_quantized", (q8, scale))
    all_q = jax.lax.all_gather(q8, axis)
    all_s = jax.lax.all_gather(scale, axis)
    acc = jnp.sum(all_q.astype(jnp.float32) * (all_s * (1.0 / 127.0)),
                  axis=0)
    return _dequantize_blocks(acc, x.shape[-1])


def reducescatter_quantized(x, op: Op = Op.SUM, axis: str = "data",
                            wire_dtype: str = "f32",
                            block: int = QUANT_BLOCK, fold=None):
    """:func:`reducescatter` with an opt-in quantized wire: quantize →
    exchange row blocks in the narrow dtype (+ scale planes) → ONE
    dequantized fold epilog. Rank r returns the ``op``-reduction of
    every rank's r-th row block (leading dim must divide the axis).

    ``fold`` replaces the ``op``-reduction with the caller's own
    associative merge over the dequantized ``(R, rows/R, ...)`` f32
    rank stack — the hook the 2-D mesh query×list top-k merge folds
    through (its reduction is a sort-merge, not an :class:`Op`; the
    received blocks stack in rank order, matching the allgather-merge
    candidate order exactly).

    Integer payloads always take the exact int32 wire; the pure
    ``f32``/``SUM``/no-``fold`` case lowers to the native
    psum_scatter."""
    resolve_reduce_wire_dtype(wire_dtype)
    if (wire_dtype == "f32" and op == Op.SUM and fold is None
            and not jnp.issubdtype(x.dtype, jnp.integer)):
        _count_collective("reducescatter_quantized", x)
        return jax.lax.psum_scatter(x, axis, tiled=True)
    if jnp.issubdtype(x.dtype, jnp.integer):
        xi = x.astype(jnp.int32)
        _count_collective("reducescatter_quantized", xi)
        stack = _alltoall_impl(xi, axis).astype(x.dtype)
    elif wire_dtype == "f32":
        _count_collective("reducescatter_quantized", x)
        stack = _alltoall_impl(x, axis)
    elif wire_dtype == "bf16":
        xw = x.astype(jnp.bfloat16)
        _count_collective("reducescatter_quantized", xw)
        stack = _alltoall_impl(xw, axis).astype(jnp.float32)
    else:
        if op != Op.SUM and fold is None:
            raise ValueError(
                f"quantized reducescatter wires are SUM-only, got {op}")
        q8, scale = _quantize_blocks(x, block)
        _count_collective("reducescatter_quantized", (q8, scale))
        all_q = _alltoall_impl(q8, axis)
        all_s = _alltoall_impl(scale, axis)
        stack = _dequantize_blocks(
            all_q.astype(jnp.float32) * (all_s * (1.0 / 127.0)),
            x.shape[-1])
    if fold is not None:
        return fold(stack)
    if op == Op.SUM:
        return jnp.sum(stack, axis=0)
    if op == Op.MAX:
        return jnp.max(stack, axis=0)
    if op == Op.MIN:
        return jnp.min(stack, axis=0)
    return jnp.prod(stack, axis=0)


# wire formats for the coarse/probe-candidate exchange: the payload is
# *candidate scores* (compared, never accumulated), so it tolerates a
# harder squeeze than the result merge — int8 with a per-row affine
# scale pair (the EQuARX block-scaling recipe) quarters the bytes of f32
PROBE_WIRE_DTYPES = ("f32", "bf16", "int8")


def resolve_probe_wire_dtype(wire_dtype: str) -> str:
    """Validate a probe-exchange ``wire_dtype`` (identity mapping —
    ``int8`` has no jnp carrier; :func:`allgather_quantized` packs it
    with an explicit per-row scale plane)."""
    if wire_dtype not in PROBE_WIRE_DTYPES:
        raise ValueError(
            f"probe wire_dtype must be one of {PROBE_WIRE_DTYPES}, "
            f"got {wire_dtype!r}")
    return wire_dtype


def allgather_quantized(x, axis: str = "data", wire_dtype: str = "f32",
                        scale_ref=None):
    """:func:`allgather` of a (rows, n) score block with an opt-in
    quantized wire format, dequantized after the collective:

    - ``"f32"`` / ``"bf16"``: :func:`allgather_wire` (cast-only).
    - ``"int8"``: affine per-row quantization — each row travels as
      int8 codes plus TWO f32 planes (the row's minimum and range), so
      the payload is ~1/4 of f32 for n >> 1. Rounding is
      round-half-to-even (jnp.round), deterministic across shards.

    ``scale_ref`` (int8 only) supplies the block the per-row affine
    scales derive from — pass the FULL pre-selection score block when
    ``x`` is a selected subset, and the codes become independent of
    *which* candidates were selected (and of how many): the
    block-independence the ragged serving family's cap-vs-solo
    bit-identity contract needs (PR 17 retired the int8 ragged pin on
    exactly this property). Quantization is monotone per row, so
    ranking survives up to the ties it creates — the caller must break
    those deterministically (the probe selects sort by
    (distance, id))."""
    if wire_dtype != "int8":
        return allgather_wire(x, axis, wire_dtype)
    ref = x if scale_ref is None else scale_ref
    lo = jnp.min(ref, axis=-1, keepdims=True)
    span = jnp.max(ref, axis=-1, keepdims=True) - lo
    span = jnp.maximum(span, jnp.finfo(jnp.float32).tiny)
    q8 = jnp.clip(jnp.round((x - lo) * (254.0 / span)) - 127.0,
                  -127, 127).astype(jnp.int8)
    _count_collective("allgather_quantized", (q8, lo, span))
    all_q = jax.lax.all_gather(q8, axis)
    all_lo = jax.lax.all_gather(lo, axis)
    all_sp = jax.lax.all_gather(span, axis)
    return ((all_q.astype(jnp.float32) + 127.0) * (all_sp * (1.0 / 254.0))
            + all_lo)


def gather(x, root: int = 0, axis: str = "data", tiled: bool = False):
    """``comms_t::gather`` (valid on every rank, superset of reference;
    per-link cost on ICI matches a rooted gather — see
    :func:`reduce`)."""
    _count_collective("gather", x)
    return jax.lax.all_gather(x, axis, tiled=tiled)


def allgatherv(x, valid_size, axis: str = "data"):
    """``comms_t::allgatherv``: ragged gather emulated with the padded
    block + per-rank sizes (TPU collectives need static shapes).

    Returns (stacked (n_ranks, max_block, ...), sizes (n_ranks,))."""
    sizes = jnp.asarray(valid_size, jnp.int32)
    _count_collective("allgatherv", (x, sizes))   # both wire payloads
    return (
        jax.lax.all_gather(x, axis),
        jax.lax.all_gather(sizes, axis),
    )


def reducescatter(x, op: Op = Op.SUM, axis: str = "data"):
    """``comms_t::reducescatter`` → psum_scatter over the leading dim."""
    _count_collective("reducescatter", x)
    if op != Op.SUM:
        gathered = _allreduce_impl(x, op, axis)
        n = axis_size(axis)
        rank = jax.lax.axis_index(axis)
        block = x.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(gathered, rank * block, block)
    return jax.lax.psum_scatter(x, axis, tiled=True)


def _alltoall_impl(x, axis: str):
    """Uncounted all-to-all body — :func:`reducescatter_quantized`'s
    row-block exchange routes through this so one logical quantized
    collective bumps the ledger exactly once, under its own family."""
    n = axis_size(axis)
    blocks = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    return jax.lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0)


def alltoall(x, axis: str = "data"):
    """``comms_t`` device_multicast/alltoall: exchange row blocks so rank
    r receives block r from every rank (``lax.all_to_all``)."""
    _count_collective("alltoall", x)
    return _alltoall_impl(x, axis)


def _ring_permute(x, offset: int, axis: str):
    """Uncounted ring-shift body shared by send/recv (each veneer
    bumps its own ledger family exactly once)."""
    n = axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def device_send(x, dest_offset: int = 1, axis: str = "data"):
    """Ring send: rank r's value moves to rank (r + dest_offset) % n —
    the p2p pattern expressible on the ICI torus (``comms_t::device_send``;
    arbitrary pairs route through :func:`device_sendrecv` perms)."""
    _count_collective("device_send", x)
    return _ring_permute(x, dest_offset, axis)


def device_recv(x, src_offset: int = 1, axis: str = "data"):
    """Ring recv: receive the value from rank (r - src_offset) % n."""
    _count_collective("device_recv", x)
    return _ring_permute(x, src_offset, axis)


def device_sendrecv(x, perm: Sequence[tuple], axis: str = "data"):
    """``comms_t::device_sendrecv``: explicit (src, dst) pair list."""
    _count_collective("device_sendrecv", x)
    return jax.lax.ppermute(x, axis, list(perm))


def mark_varying(x, axis: str = "data"):
    """Mark a value device-varying for shard_map's validity check —
    the version shim for the pvary → pcast migration: jax 0.7+ spells
    it ``pcast(..., to="varying")``, 0.6 has ``pvary``, and 0.4.x/0.5.x
    have neither and need no marking (their shard_map runs these
    programs with ``check_rep=False``). Like :func:`shard_map` and
    :func:`axis_size`, this is THE spelling mesh programs use — a raw
    feature probe at a call site would re-fork on every jax bump."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis)
    return x


def barrier(axis: str = "data"):
    """``comms_t::barrier`` / ``sync_stream``: a psum fence all ranks
    must reach; returns the rank count."""
    return jax.lax.psum(jnp.ones((), jnp.int32), axis)


def rank(axis: str = "data"):
    """``comms_t::get_rank``."""
    return jax.lax.axis_index(axis)


def size(axis: str = "data"):
    """``comms_t::get_size``."""
    return axis_size(axis)


# ---------------------------------------------------------------------------
# Comms handle
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Comms:
    """Mesh + axis handle injected into :class:`~raft_tpu.core.Resources`
    (role of ``std_comms`` built by ``build_comms_nccl_only``,
    ``comms/std_comms.hpp:69``, and of raft-dask's ``Comms``,
    ``raft_dask/common/comms.py:39``).

    ``axis`` is the mesh axis this communicator spans; ``split`` carves
    sub-communicators out of a multi-axis mesh the way ``comm_split`` +
    ``set_subcomm`` build 2D process grids (``core/resource/sub_comms.hpp``).
    """

    mesh: Mesh
    axis: str = "data"

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def nranks(self) -> int:
        return self.size

    @property
    def process_rank(self) -> int:
        """This *process*'s rank (multi-host); device-level rank is
        :func:`rank` inside the mapped program."""
        return jax.process_index()

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding over this comms' mesh."""
        return NamedSharding(self.mesh, P(*spec))

    def row_sharded(self) -> NamedSharding:
        return self.sharding(self.axis)

    def replicated(self) -> NamedSharding:
        return self.sharding()

    def run(
        self,
        fn: Callable,
        *args,
        in_specs,
        out_specs,
        check_vma: bool = True,
    ):
        """shard_map ``fn`` over this mesh: the body may call the module's
        collectives with ``axis=self.axis``."""
        return shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )(*args)

    def split(self, axis: str) -> "Comms":
        """Sub-communicator over another axis of the same mesh
        (``comms_t::comm_split`` for static 2D grids)."""
        if axis not in self.mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh {self.mesh.axis_names}")
        return Comms(self.mesh, axis)

    # -- self-tests (role of comms/comms_test.hpp:34-118) --------------------

    def test_allreduce(self) -> bool:
        n = self.size
        x = jnp.arange(n, dtype=jnp.float32)
        out = self.run(
            lambda v: allreduce(v, Op.SUM, self.axis),
            jax.device_put(x, self.row_sharded()),
            in_specs=P(self.axis), out_specs=P(self.axis),
        )
        return bool(jnp.all(out == jnp.sum(x)))

    def test_bcast(self, root: int = 0) -> bool:
        n = self.size
        x = jnp.arange(n, dtype=jnp.float32) + 3
        out = self.run(
            lambda v: bcast(v, root, self.axis),
            jax.device_put(x, self.row_sharded()),
            in_specs=P(self.axis), out_specs=P(self.axis),
        )
        return bool(jnp.all(out == x[root]))

    def test_pointToPoint_simple_send_recv(self) -> bool:
        n = self.size
        x = jnp.arange(n, dtype=jnp.float32)
        out = self.run(
            lambda v: device_send(v, 1, self.axis),
            jax.device_put(x, self.row_sharded()),
            in_specs=P(self.axis), out_specs=P(self.axis),
        )
        return bool(jnp.all(out == jnp.roll(x, 1)))
