"""Dataset IO — big-ann-benchmarks binary formats via the native C++
runtime (``native/io.cpp``), with a numpy fallback.

Analog of the reference's ``bench/ann/src/common/dataset.hpp`` (C++
``BinFile<T>`` mmap loader) and the ``raft-ann-bench`` dataset tooling.
"""

from raft_tpu.io.binfile import (
    BinDataset,
    native_available,
    read_bin,
    write_bin,
)

__all__ = ["BinDataset", "native_available", "read_bin", "write_bin"]
