"""``.fbin`` / ``.u8bin`` / ``.i8bin`` readers and writers.

Primary path: the native C++ library (``native/io.cpp`` — mmap +
threaded reads, the ``BinFile<T>`` analog of the reference's
``bench/ann/src/common/dataset.hpp:45-145``), loaded via ctypes and
compiled on demand with the in-repo Makefile. Fallback: numpy memmap,
so the package works where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

_SUFFIX_DTYPES = {
    ".fbin": np.float32,
    ".u8bin": np.uint8,
    ".i8bin": np.int8,
    ".ibin": np.int32,   # groundtruth index files
}

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_SO_PATH = _NATIVE_DIR / "libraft_tpu_io.so"

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _dtype_for(path: str):
    suffix = pathlib.Path(path).suffix
    if suffix not in _SUFFIX_DTYPES:
        raise ValueError(
            f"unknown dataset suffix {suffix!r}; expected one of "
            f"{sorted(_SUFFIX_DTYPES)}"
        )
    return np.dtype(_SUFFIX_DTYPES[suffix])


def _load_native():
    """Load (building if needed) the native IO library; None if impossible."""
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _SO_PATH.exists() and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(
                    ["make", "-s"], cwd=_NATIVE_DIR, check=True,
                    capture_output=True, timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                return None
        if not _SO_PATH.exists():
            return None
        try:
            lib = ctypes.CDLL(str(_SO_PATH))
        except OSError:
            return None
        lib.rt_io_open.restype = ctypes.c_void_p
        lib.rt_io_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.rt_io_rows.restype = ctypes.c_int64
        lib.rt_io_rows.argtypes = [ctypes.c_void_p]
        lib.rt_io_dim.restype = ctypes.c_int64
        lib.rt_io_dim.argtypes = [ctypes.c_void_p]
        lib.rt_io_last_error.restype = ctypes.c_char_p
        lib.rt_io_read_rows.restype = ctypes.c_int
        lib.rt_io_read_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.rt_io_close.argtypes = [ctypes.c_void_p]
        lib.rt_io_create.restype = ctypes.c_void_p
        lib.rt_io_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.rt_io_append_rows.restype = ctypes.c_int
        lib.rt_io_append_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.rt_io_close_writer.restype = ctypes.c_int
        lib.rt_io_close_writer.argtypes = [ctypes.c_void_p]
        lib.rt_io_pipeline_start.restype = ctypes.c_void_p
        lib.rt_io_pipeline_start.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        lib.rt_io_pipeline_next.restype = ctypes.c_int
        lib.rt_io_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rt_io_pipeline_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_native() is not None


class BinDataset:
    """Windowed access to a big-ann bin file — the ``BinFile<T>`` +
    subset view combination the reference bench uses for 100M+ row
    datasets (``dataset.hpp`` subset ctor)."""

    def __init__(self, path, *, use_native: Optional[bool] = None):
        self.path = str(path)
        self.dtype = _dtype_for(self.path)
        if use_native is None:
            use_native = native_available()
        elif use_native and not native_available():
            raise IOError(
                "use_native=True but the native IO library is unavailable "
                "(build failed or no toolchain); pass use_native=None to "
                "allow the numpy fallback"
            )
        self._native = use_native
        if self._native:
            lib = _load_native()
            handle = lib.rt_io_open(
                self.path.encode(), self.dtype.itemsize
            )
            if not handle:
                raise IOError(
                    f"native open failed: "
                    f"{lib.rt_io_last_error().decode()}"
                )
            self._handle = handle
            self.n_rows = int(lib.rt_io_rows(handle))
            self.dim = int(lib.rt_io_dim(handle))
        else:
            self._handle = None
            header = np.fromfile(self.path, np.int32, 2)
            if header.size != 2 or header[1] <= 0 or header[0] < 0:
                raise IOError(f"bad bin header in {self.path}")
            self.n_rows, self.dim = int(header[0]), int(header[1])
            expected = 8 + self.n_rows * self.dim * self.dtype.itemsize
            actual = os.path.getsize(self.path)
            if expected > actual:
                raise IOError(
                    f"truncated bin file {self.path}: header promises "
                    f"{expected} bytes, file has {actual}"
                )

    @property
    def shape(self):
        return (self.n_rows, self.dim)

    def read(self, row_start: int = 0, n_rows: Optional[int] = None,
             n_threads: int = 0) -> np.ndarray:
        """Copy rows [row_start, row_start + n_rows) into a fresh array."""
        if n_rows is None:
            n_rows = self.n_rows - row_start
        if row_start < 0 or n_rows < 0 or row_start + n_rows > self.n_rows:
            raise IndexError("read out of bounds")
        out = np.empty((n_rows, self.dim), self.dtype)
        if self._native:
            lib = _load_native()
            rc = lib.rt_io_read_rows(
                self._handle, row_start, n_rows,
                out.ctypes.data_as(ctypes.c_void_p), n_threads,
            )
            if rc != 0:
                raise IOError(lib.rt_io_last_error().decode())
        else:
            mm = np.memmap(self.path, self.dtype, mode="r", offset=8,
                           shape=(self.n_rows, self.dim))
            out[:] = mm[row_start : row_start + n_rows]
            del mm
        return out

    def iter_chunks(self, chunk_rows: int, n_threads: int = 0,
                    copy: bool = True):
        """Yield ``(first_row, array)`` chunks in order.

        On the native path a background C++ thread prefetches chunk i+1
        while chunk i is being consumed (double-buffered) — the streaming
        ingestion path for datasets far larger than memory. With
        ``copy=False`` the yielded array is a view into the prefetch
        buffer and is only valid until the next iteration (fine when the
        next step is an immediate ``jax.device_put``).
        """
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        if not self._native:
            for start in range(0, self.n_rows, chunk_rows):
                n = min(chunk_rows, self.n_rows - start)
                yield start, self.read(start, n)
            return
        lib = _load_native()
        pipe = lib.rt_io_pipeline_start(self._handle, chunk_rows, n_threads)
        if not pipe:
            raise IOError(lib.rt_io_last_error().decode())
        try:
            data_p = ctypes.c_void_p()
            first = ctypes.c_int64()
            nrows = ctypes.c_int64()
            while True:
                rc = lib.rt_io_pipeline_next(
                    pipe, ctypes.byref(data_p), ctypes.byref(first),
                    ctypes.byref(nrows),
                )
                if rc == 1:
                    return
                if rc != 0:
                    raise IOError(lib.rt_io_last_error().decode())
                n = int(nrows.value)
                buf = (ctypes.c_char * (n * self.dim
                                        * self.dtype.itemsize)
                       ).from_address(data_p.value)
                arr = np.frombuffer(buf, self.dtype).reshape(n, self.dim)
                yield int(first.value), (arr.copy() if copy else arr)
        finally:
            lib.rt_io_pipeline_close(pipe)

    def close(self):
        if self._native and self._handle is not None:
            _load_native().rt_io_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_bin(path, row_start: int = 0, n_rows: Optional[int] = None,
             **kwargs) -> np.ndarray:
    with BinDataset(path, **kwargs) as ds:
        return ds.read(row_start, n_rows)


def write_bin(path, data: np.ndarray, *,
              use_native: Optional[bool] = None) -> None:
    """Write a (n, d) array in big-ann bin layout (dtype from suffix)."""
    data = np.ascontiguousarray(data, dtype=_dtype_for(str(path)))
    if data.ndim != 2:
        raise ValueError("write_bin expects (n, d) data")
    if use_native is None:
        use_native = native_available()
    elif use_native and not native_available():
        raise IOError(
            "use_native=True but the native IO library is unavailable "
            "(build failed or no toolchain); pass use_native=None to "
            "allow the numpy fallback"
        )
    if use_native:
        lib = _load_native()
        h = lib.rt_io_create(str(path).encode(), data.shape[0],
                             data.shape[1], data.dtype.itemsize)
        if not h:
            raise IOError(lib.rt_io_last_error().decode())
        if lib.rt_io_append_rows(
            h, data.ctypes.data_as(ctypes.c_void_p), data.shape[0]
        ) != 0:
            lib.rt_io_close_writer(h)
            raise IOError(lib.rt_io_last_error().decode())
        if lib.rt_io_close_writer(h) != 0:
            raise IOError(lib.rt_io_last_error().decode())
    else:
        with open(path, "wb") as fh:
            np.asarray(data.shape, np.int32).tofile(fh)
            data.tofile(fh)
