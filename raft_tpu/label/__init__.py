"""Label utilities — analog of ``raft/label/`` (``classlabels.cuh``:
``getUniquelabels`` / ``getOvrlabels`` / ``make_monotonic``;
``merge_labels.cuh``: union-find label merge).
"""

from raft_tpu.label.classlabels import (
    get_unique_labels,
    make_monotonic,
    merge_labels,
    ovr_labels,
)

__all__ = [
    "get_unique_labels",
    "make_monotonic",
    "merge_labels",
    "ovr_labels",
]
