"""Class-label utilities — ``raft/label/classlabels.cuh`` and
``raft/label/merge_labels.cuh``.

``merge_labels`` is the reference's union-find-flavored label
reconciliation used by connected components; on TPU it is pointer
jumping over a static min-label table — ``ceil(log2 n)`` fixed rounds.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources


def get_unique_labels(res: Optional[Resources], labels) -> jax.Array:
    """Sorted unique labels — ``label::getUniquelabels``. Host-side
    (result size is data-dependent, like the reference's two-pass
    count+fill)."""
    return jnp.asarray(np.unique(np.asarray(labels)))


def make_monotonic(
    res: Optional[Resources], labels, classes=None
) -> jax.Array:
    """Map arbitrary label values onto 0..n_classes-1 —
    ``label::make_monotonic``."""
    if classes is None:
        classes = get_unique_labels(res, labels)
    labels = jnp.asarray(labels)
    # rank of each label within the sorted class table
    return jnp.searchsorted(classes, labels).astype(jnp.int32)


def ovr_labels(res: Optional[Resources], labels, target) -> jax.Array:
    """One-vs-rest relabeling: 1 where ``labels == target`` else 0 —
    ``label::getOvrlabels``."""
    return (jnp.asarray(labels) == target).astype(jnp.int32)


def merge_labels(
    res: Optional[Resources],
    labels_a,
    labels_b,
    mask=None,
) -> jax.Array:
    """Merge two label assignments: rows sharing a label in either input
    end up with one common (minimum) label — ``label::merge_labels``
    (``merge_labels.cuh``; used to stitch connected components computed
    in batches).

    ``mask`` restricts which rows participate (unmasked rows keep
    ``labels_a``).
    """
    la = jnp.asarray(labels_a, jnp.int32)
    lb = jnp.asarray(labels_b, jnp.int32)
    n = la.shape[0]
    if mask is None:
        mask = jnp.ones((n,), bool)

    # representative per b-group: min a-label in the group; then propagate
    # a→rep(a) links by pointer jumping until fixed point
    n_groups = n  # b-labels are < n by construction in CC usage
    rounds = max(1, int(np.ceil(np.log2(max(n, 2)))))

    def body(_, lab):
        grp_min = jax.ops.segment_min(
            jnp.where(mask, lab, jnp.iinfo(jnp.int32).max),
            jnp.where(mask, lb, n_groups - 1),
            num_segments=n_groups,
        )
        new = jnp.where(mask, jnp.minimum(lab, jnp.take(grp_min, lb)), lab)
        # chase a-labels: label of my label's row (labels index rows in CC)
        chased = jnp.take(new, jnp.clip(new, 0, n - 1))
        return jnp.where(mask, jnp.minimum(new, chased), new)

    return jax.lax.fori_loop(0, rounds, body, la)
