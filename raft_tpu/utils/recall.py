"""Recall evaluation with distance-tie tolerance — analog of
``cpp/test/neighbors/ann_utils.cuh:127-210`` (``eval_recall`` /
``eval_neighbours``), promoted into the library because the benchmark
harness uses it too (``bench/ann/src/common/benchmark.hpp`` recall
counter)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


def eval_recall(
    expected_idx,
    actual_idx,
    expected_dist=None,
    actual_dist=None,
    eps: float = 1e-3,
) -> Tuple[float, int, int]:
    """Fraction of true neighbors found, counting distance-ties as hits.

    A returned neighbor that is not in the ground-truth id set still counts
    if its distance matches a ground-truth distance within ``eps`` (the
    reference's tie handling).

    Returns (recall, n_match, n_total).
    """
    expected_idx = np.asarray(expected_idx)
    actual_idx = np.asarray(actual_idx)
    q, k = expected_idx.shape
    match = 0
    for i in range(q):
        want = set(expected_idx[i].tolist())
        got = actual_idx[i].tolist()
        for j, g in enumerate(got):
            if g in want:
                match += 1
            elif expected_dist is not None and actual_dist is not None:
                ad = actual_dist[i, j]
                if np.any(np.abs(np.asarray(expected_dist[i]) - ad) <= eps * max(1.0, abs(ad))):
                    match += 1
    return match / (q * k), match, q * k


def eval_neighbours(
    expected_idx,
    actual_idx,
    expected_dist,
    actual_dist,
    min_recall: float,
    eps: float = 1e-3,
) -> float:
    """Assert-style evaluation (``eval_neighbours``): returns recall, raises
    AssertionError below ``min_recall`` (with slack eps on the threshold,
    matching the reference's error bound)."""
    recall, match, total = eval_recall(
        expected_idx, actual_idx, expected_dist, actual_dist, eps
    )
    if recall < min_recall - eps:
        raise AssertionError(
            f"recall {recall:.4f} ({match}/{total}) below required {min_recall:.4f}"
        )
    return recall
