"""Shared utilities (reference ``raft/util/`` + test-support helpers)."""

from raft_tpu.utils.recall import eval_recall, eval_neighbours

__all__ = ["eval_recall", "eval_neighbours"]
